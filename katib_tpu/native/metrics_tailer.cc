// Native metrics tailer: incremental file tailing + TEXT metric-line parsing
// for the trial executor's watch loop.
//
// The reference's equivalent surface is the Go file-metrics-collector sidecar
// (cmd/metricscollector/v1beta1/file-metricscollector/main.go:336-386): a
// fsnotify watch over the metrics file applying the TEXT filter per line to
// enforce early-stopping rules while the trial runs. In this framework the
// orchestrator process tails every running trial's output itself (often 64+
// concurrent trials on one host core), so the per-poll work — read new
// bytes, split lines, regex-scan for `name = value` pairs — is a hot path
// worth doing in native code.
//
// Semantics mirror katib_tpu.runtime.metrics.DEFAULT_FILTER:
//     ([\w|-]+)\s*=\s*([+-]?\d*(\.\d+)?([Ee][+-]?\d+)?)
// applied with finditer over each complete line, keeping only wanted metric
// names whose value parses as a float. Partial trailing lines are buffered
// across polls exactly like the Python loop in SubprocessExecutor._wait.
//
// C ABI (ctypes): mt_open(path, names) -> handle; mt_poll(handle) -> malloc'd
// "name\x1Fvalue\x1Fline_index\n"* (NULL when no new matches); mt_free;
// mt_close. Line indices increase monotonically across polls so the Python
// side can synthesize report-order timestamps.

#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_set>

namespace {

struct Tailer {
  std::string path;
  long offset = 0;
  std::string partial;
  std::unordered_set<std::string> wanted;
  long line_index = 0;
};

inline bool name_char(char c) {
  unsigned char u = static_cast<unsigned char>(c);
  return std::isalnum(u) || c == '_' || c == '|' || c == '-';
}

inline bool pure_ascii(const std::string& s) {
  for (char c : s)
    if (static_cast<unsigned char>(c) >= 0x80) return false;
  return true;
}

// Parse the value part of `name = value` starting at s[i]; on success returns
// true and sets [begin,end) of the numeric text and advances i past it.
bool parse_value(const std::string& s, size_t& i, size_t& begin, size_t& end) {
  size_t j = i;
  if (j < s.size() && (s[j] == '+' || s[j] == '-')) ++j;
  size_t digits_start = j;
  while (j < s.size() && std::isdigit(static_cast<unsigned char>(s[j]))) ++j;
  bool has_int = j > digits_start;
  bool has_frac = false;
  if (j < s.size() && s[j] == '.') {
    size_t k = j + 1;
    while (k < s.size() && std::isdigit(static_cast<unsigned char>(s[k]))) ++k;
    if (k > j + 1) {  // regex requires \.\d+ — at least one digit
      has_frac = true;
      j = k;
    }
  }
  if (!has_int && !has_frac) return false;
  if (j < s.size() && (s[j] == 'e' || s[j] == 'E')) {
    size_t k = j + 1;
    if (k < s.size() && (s[k] == '+' || s[k] == '-')) ++k;
    size_t exp_start = k;
    while (k < s.size() && std::isdigit(static_cast<unsigned char>(s[k]))) ++k;
    if (k > exp_start) j = k;  // exponent only counts with >= 1 digit
  }
  begin = i;
  end = j;
  i = j;
  return true;
}

// finditer(DEFAULT_FILTER, line): append matches to out.
void scan_line(const Tailer& t, const std::string& line, long index,
               std::string& out) {
  size_t i = 0;
  const size_t n = line.size();
  while (i < n) {
    if (!name_char(line[i])) {
      ++i;
      continue;
    }
    size_t name_start = i;
    while (i < n && name_char(line[i])) ++i;
    size_t name_end = i;
    size_t j = i;
    while (j < n && (line[j] == ' ' || line[j] == '\t')) ++j;
    if (j >= n || line[j] != '=') continue;  // resume after the name run
    ++j;
    while (j < n && (line[j] == ' ' || line[j] == '\t')) ++j;
    size_t vb = 0, ve = 0;
    if (!parse_value(line, j, vb, ve)) continue;
    i = j;  // continue scanning after the value (finditer semantics)
    std::string name = line.substr(name_start, name_end - name_start);
    if (!t.wanted.count(name)) continue;
    out += name;
    out += '\x1F';
    out.append(line, vb, ve - vb);
    out += '\x1F';
    out += std::to_string(index);
    out += '\n';
  }
}

}  // namespace

extern "C" {

void* mt_open(const char* path, const char* names) {
  Tailer* t = new Tailer();
  t->path = path;
  const char* start = names;
  for (const char* p = names;; ++p) {
    if (*p == '\x1F' || *p == '\0') {
      if (p > start) t->wanted.emplace(start, static_cast<size_t>(p - start));
      if (*p == '\0') break;
      start = p + 1;
    }
  }
  return t;
}

char* mt_poll(void* handle) {
  Tailer* t = static_cast<Tailer*>(handle);
  FILE* f = std::fopen(t->path.c_str(), "rb");
  if (f == nullptr) return nullptr;
  if (std::fseek(f, t->offset, SEEK_SET) != 0) {
    std::fclose(f);
    return nullptr;
  }
  std::string data;
  char buf[65536];
  size_t got;
  while ((got = std::fread(buf, 1, sizeof(buf), f)) > 0) {
    data.append(buf, got);
    t->offset += static_cast<long>(got);
  }
  std::fclose(f);
  if (data.empty()) return nullptr;

  std::string out;
  size_t pos = 0;
  t->partial.append(data);
  while (true) {
    size_t nl = t->partial.find('\n', pos);
    if (nl == std::string::npos) break;
    std::string line = t->partial.substr(pos, nl - pos);
    if (pure_ascii(line)) {
      scan_line(*t, line, t->line_index++, out);
    } else {
      // Non-ASCII line: Python's \w is Unicode-aware and a byte-oriented
      // matcher cannot reproduce its word boundaries, so hand the raw line
      // back for the binding to parse with the real regex ('\x02' record:
      // index \x1F line).
      out += '\x02';
      out += std::to_string(t->line_index++);
      out += '\x1F';
      out += line;
      out += '\n';
    }
    pos = nl + 1;
  }
  t->partial.erase(0, pos);

  if (out.empty()) return nullptr;
  char* res = static_cast<char*>(std::malloc(out.size() + 1));
  if (res == nullptr) return nullptr;
  std::memcpy(res, out.data(), out.size());
  res[out.size()] = '\0';
  return res;
}

void mt_free(char* buf) { std::free(buf); }

void mt_close(void* handle) { delete static_cast<Tailer*>(handle); }

}  // extern "C"
