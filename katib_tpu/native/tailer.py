"""Trial-output tailer: native (metrics_tailer.cc via ctypes) with a pure
Python fallback.

The executor's watch loop (SubprocessExecutor._wait) polls every running
trial's stdout/metrics file for `name = value` lines to enforce
early-stopping rules — the in-process equivalent of the reference's
file-metrics-collector sidecar watch
(file-metricscollector/main.go:336-386). With 64 concurrent trials on the
single orchestrator core, reading + regex-scanning in Python is measurable
overhead; the native tailer does the read/split/parse in C++.

``make_tailer`` picks the implementation: native when the shared object is
built and the collector uses the default TEXT filter; Python otherwise
(custom regex filters and JSON lines keep full generality).
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence, Tuple

from . import METRICS_TAILER_SO, tailer_available

# (metric_name, raw_value, line_index) — line_index is monotonically
# increasing across polls so callers can synthesize report-order timestamps
Parsed = Tuple[str, str, int]

_lib = None


def _load_lib():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(METRICS_TAILER_SO)
        lib.mt_open.restype = ctypes.c_void_p
        lib.mt_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.mt_poll.restype = ctypes.POINTER(ctypes.c_char)
        lib.mt_poll.argtypes = [ctypes.c_void_p]
        lib.mt_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
        lib.mt_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class NativeTailer:
    def __init__(self, path: str, metric_names: Sequence[str]):
        self._lib = _load_lib()
        self._names = list(metric_names)
        names = "\x1f".join(metric_names).encode()
        self._handle = self._lib.mt_open(path.encode(), names)

    def poll(self) -> List[Parsed]:
        buf = self._lib.mt_poll(self._handle)
        if not buf:
            return []
        try:
            raw = ctypes.string_at(buf).decode("utf-8", errors="replace")
        finally:
            self._lib.mt_free(buf)
        out: List[Parsed] = []
        # records are framed with '\n' by the C++ side; str.splitlines()
        # would also split on \v, \f, NEL, U+2028/9 inside deferred
        # non-ASCII lines, corrupting their records
        for entry in raw.split("\n"):
            if entry.startswith("\x02"):
                # non-ASCII line deferred by the kernel: parse with the real
                # Unicode-aware regex (same path as PyTailer)
                from ..runtime.metrics import parse_text_lines

                idx_str, _, line = entry[1:].partition("\x1f")
                for log in parse_text_lines([line], self._names):
                    try:
                        float(log.value)
                    except (TypeError, ValueError):
                        continue
                    out.append((log.metric_name, log.value, int(idx_str)))
                continue
            parts = entry.split("\x1f")
            if len(parts) == 3:
                out.append((parts[0], parts[1], int(parts[2])))
        return out

    def close(self) -> None:
        if self._handle:
            self._lib.mt_close(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; executor calls close() explicitly
        try:
            self.close()
        except Exception:
            pass


class PyTailer:
    """Fallback replicating the original executor loop: offset + partial-line
    buffer, parse via runtime.metrics (supports custom filters and JSON)."""

    def __init__(
        self,
        path: str,
        metric_names: Sequence[str],
        filters: Optional[Sequence[str]] = None,
        json_format: bool = False,
    ):
        self._path = path
        self._names = list(metric_names)
        self._filters = list(filters) if filters else None
        self._json = json_format
        self._offset = 0
        self._buffered = ""
        self._line_index = 0

    def poll(self) -> List[Parsed]:
        from ..runtime.metrics import parse_json_lines, parse_text_lines

        if not os.path.exists(self._path):
            return []
        with open(self._path, "r", errors="replace") as f:
            f.seek(self._offset)
            chunk = f.read()
            self._offset = f.tell()
        if not chunk:
            return []
        self._buffered += chunk
        lines = self._buffered.split("\n")
        self._buffered = lines.pop()
        out: List[Parsed] = []
        for line in lines:
            idx = self._line_index
            self._line_index += 1
            if self._json:
                logs = parse_json_lines([line], self._names)
            else:
                logs = parse_text_lines([line], self._names, self._filters)
            for log in logs:
                # tailer contract: values are float-parseable (the regex's
                # value group can match a bare sign; the native tailer
                # rejects those in-kernel, and consumers would skip them)
                try:
                    float(log.value)
                except (TypeError, ValueError):
                    continue
                out.append((log.metric_name, log.value, idx))
        return out

    def close(self) -> None:
        pass


def make_tailer(
    path: str,
    metric_names: Sequence[str],
    filters: Optional[Sequence[str]] = None,
    json_format: bool = False,
):
    """Native tailer for the default-TEXT-filter case; Python otherwise
    (custom filters or JSON lines). Non-ASCII lines are deferred by the
    kernel back to the Unicode-aware Python regex, so Unicode metric names
    and log content parse identically on both paths."""
    if not json_format and not filters and tailer_available():
        try:
            return NativeTailer(path, metric_names)
        except OSError:
            pass
    return PyTailer(path, metric_names, filters, json_format)
