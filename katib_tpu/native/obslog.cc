// Native observation-log store engine.
//
// C++ counterpart of the reference's data plane (katib-db-manager gRPC server
// + observation_logs table — reference cmd/db-manager/v1beta1/main.go,
// pkg/db/v1beta1/mysql/mysql.go:67-166). The schema is the same logical row
// (trial_name, time, metric_name, value); storage is an append-only binary
// log per store with an in-memory per-trial index, rebuilt on open by a
// single sequential scan.
//
// Record framing (little-endian):
//   u32 magic 'KTOB' | u32 record_len | f64 time | u16 trial_len |
//   u16 metric_len | u16 value_len | bytes... (trial, metric, value)
// Deletes append a tombstone (trial_len with high bit set); compaction is a
// rewrite on close when enough rows are dead.
//
// Exposed as a C ABI consumed via ctypes (katib_tpu/native/__init__.py);
// python-side fallback is the SQLite store, so the framework runs without a
// compiler present.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x424F544B;  // 'KTOB'
constexpr uint16_t kTombstone = 0x8000;

struct Row {
  double time;
  std::string metric;
  std::string value;
};

struct Store {
  std::mutex mu;
  std::string path;
  FILE* f = nullptr;
  std::unordered_map<std::string, std::vector<Row>> index;
  size_t dead_rows = 0;
  size_t live_rows = 0;
};

bool write_record(FILE* f, const std::string& trial, const Row& row,
                  bool tombstone) {
  uint16_t tlen = static_cast<uint16_t>(trial.size());
  if (tombstone) tlen |= kTombstone;
  uint16_t mlen = static_cast<uint16_t>(row.metric.size());
  uint16_t vlen = static_cast<uint16_t>(row.value.size());
  uint32_t rec_len = 8 + 2 + 2 + 2 + (tlen & ~kTombstone) + mlen + vlen;
  if (std::fwrite(&kMagic, 4, 1, f) != 1) return false;
  if (std::fwrite(&rec_len, 4, 1, f) != 1) return false;
  if (std::fwrite(&row.time, 8, 1, f) != 1) return false;
  if (std::fwrite(&tlen, 2, 1, f) != 1) return false;
  if (std::fwrite(&mlen, 2, 1, f) != 1) return false;
  if (std::fwrite(&vlen, 2, 1, f) != 1) return false;
  if (!trial.empty() && std::fwrite(trial.data(), trial.size(), 1, f) != 1)
    return false;
  if (!row.metric.empty() &&
      std::fwrite(row.metric.data(), row.metric.size(), 1, f) != 1)
    return false;
  if (!row.value.empty() &&
      std::fwrite(row.value.data(), row.value.size(), 1, f) != 1)
    return false;
  return true;
}

void load_index(Store* s) {
  FILE* f = std::fopen(s->path.c_str(), "rb");
  if (!f) return;
  while (true) {
    uint32_t magic = 0, rec_len = 0;
    if (std::fread(&magic, 4, 1, f) != 1) break;
    if (magic != kMagic) break;  // torn tail: stop at first bad frame
    if (std::fread(&rec_len, 4, 1, f) != 1) break;
    // Bound before allocating: a torn/corrupt length field must stop the
    // scan, not trigger a multi-GiB allocation. Max legal record is the
    // header plus three max-u16 strings.
    constexpr uint32_t kMaxRecord = 14 + 3u * 65535u;
    if (rec_len < 14 || rec_len > kMaxRecord) break;
    std::vector<char> buf(rec_len);
    if (std::fread(buf.data(), 1, rec_len, f) != rec_len) break;
    double time;
    uint16_t tlen, mlen, vlen;
    std::memcpy(&time, buf.data(), 8);
    std::memcpy(&tlen, buf.data() + 8, 2);
    std::memcpy(&mlen, buf.data() + 10, 2);
    std::memcpy(&vlen, buf.data() + 12, 2);
    bool tombstone = tlen & kTombstone;
    tlen &= ~kTombstone;
    if (14 + static_cast<size_t>(tlen) + mlen + vlen > rec_len) break;
    std::string trial(buf.data() + 14, tlen);
    if (tombstone) {
      auto it = s->index.find(trial);
      if (it != s->index.end()) {
        s->dead_rows += it->second.size();
        s->live_rows -= it->second.size();
        s->index.erase(it);
      }
      continue;
    }
    Row row;
    row.time = time;
    row.metric.assign(buf.data() + 14 + tlen, mlen);
    row.value.assign(buf.data() + 14 + tlen + mlen, vlen);
    s->index[trial].push_back(std::move(row));
    s->live_rows++;
  }
  std::fclose(f);
}

}  // namespace

extern "C" {

void* obslog_open(const char* path) {
  auto* s = new Store();
  s->path = path;
  load_index(s);
  s->f = std::fopen(path, "ab");
  if (!s->f) {
    delete s;
    return nullptr;
  }
  return s;
}

// rows: arrays of length n. Returns 0 on success.
int obslog_report(void* handle, const char* trial, const double* times,
                  const char** metrics, const char** values, int n) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(s->mu);
  std::string trial_s(trial);
  auto& rows = s->index[trial_s];
  for (int i = 0; i < n; i++) {
    Row row{times[i], metrics[i], values[i]};
    if (!write_record(s->f, trial_s, row, false)) return 1;
    rows.push_back(std::move(row));
    s->live_rows++;
  }
  std::fflush(s->f);
  return 0;
}

// Query rows for a trial; metric may be null; start/end may be NaN (no bound).
// Results are written as a packed buffer the caller frees with obslog_free:
//   n (i32) then per row: f64 time, u16 metric_len, u16 value_len, bytes.
// Rows are returned sorted by time (stable).
char* obslog_get(void* handle, const char* trial, const char* metric,
                 double start_time, double end_time, int64_t* out_size) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->index.find(trial);
  std::vector<const Row*> rows;
  if (it != s->index.end()) {
    for (const auto& row : it->second) {
      if (metric && row.metric != metric) continue;
      if (start_time == start_time && row.time < start_time) continue;
      if (end_time == end_time && row.time > end_time) continue;
      rows.push_back(&row);
    }
  }
  std::stable_sort(rows.begin(), rows.end(),
                   [](const Row* a, const Row* b) { return a->time < b->time; });
  size_t size = 4;
  for (const Row* r : rows) size += 8 + 2 + 2 + r->metric.size() + r->value.size();
  char* out = static_cast<char*>(std::malloc(size));
  if (!out) return nullptr;
  char* p = out;
  int32_t n = static_cast<int32_t>(rows.size());
  std::memcpy(p, &n, 4);
  p += 4;
  for (const Row* r : rows) {
    std::memcpy(p, &r->time, 8);
    p += 8;
    uint16_t mlen = static_cast<uint16_t>(r->metric.size());
    uint16_t vlen = static_cast<uint16_t>(r->value.size());
    std::memcpy(p, &mlen, 2);
    p += 2;
    std::memcpy(p, &vlen, 2);
    p += 2;
    std::memcpy(p, r->metric.data(), mlen);
    p += mlen;
    std::memcpy(p, r->value.data(), vlen);
    p += vlen;
  }
  *out_size = static_cast<int64_t>(size);
  return out;
}

int obslog_delete(void* handle, const char* trial) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(s->mu);
  Row empty{0.0, "", ""};
  if (!write_record(s->f, trial, empty, true)) return 1;
  std::fflush(s->f);
  auto it = s->index.find(trial);
  if (it != s->index.end()) {
    s->dead_rows += it->second.size();
    s->live_rows -= it->second.size();
    s->index.erase(it);
  }
  return 0;
}

void obslog_free(char* buf) { std::free(buf); }

void obslog_close(void* handle) {
  auto* s = static_cast<Store*>(handle);
  {
    std::lock_guard<std::mutex> lock(s->mu);
    if (s->f) std::fclose(s->f);
    s->f = nullptr;
    // compact when most of the file is tombstoned rows
    if (s->dead_rows > s->live_rows && s->dead_rows > 1024) {
      std::string tmp = s->path + ".compact";
      FILE* out = std::fopen(tmp.c_str(), "wb");
      if (out) {
        bool ok = true;
        for (const auto& [trial, rows] : s->index)
          for (const auto& row : rows)
            if (!write_record(out, trial, row, false)) ok = false;
        std::fclose(out);
        if (ok) std::rename(tmp.c_str(), s->path.c_str());
      }
    }
  }
  delete s;
}

}  // extern "C"
