"""Build the native components: ``python -m katib_tpu.native.build``."""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

from . import METRICS_TAILER_SO, NATIVE_DIR, OBSLOG_SO

_TARGETS = (
    ("obslog.cc", OBSLOG_SO),
    ("metrics_tailer.cc", METRICS_TAILER_SO),
)


def _build_one(gxx: str, src: str, out: str, force: bool) -> bool:
    if os.path.exists(out) and not force:
        if os.path.getmtime(out) >= os.path.getmtime(src):
            return True
    cmd = [gxx, "-O2", "-fPIC", "-shared", "-std=c++17", "-o", out, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        print(f"native build failed for {src}:\n{e.stderr}", file=sys.stderr)
        return False
    return True


def build(force: bool = False) -> bool:
    gxx = shutil.which("g++") or shutil.which("c++")
    if gxx is None:
        print("no C++ compiler found; native components unavailable", file=sys.stderr)
        return False
    ok = True
    for src_name, out in _TARGETS:
        ok = _build_one(gxx, os.path.join(NATIVE_DIR, src_name), out, force) and ok
    return ok


if __name__ == "__main__":
    ok = build(force="--force" in sys.argv)
    print("built" if ok else "build failed:", ", ".join(out for _, out in _TARGETS))
    sys.exit(0 if ok else 1)
