"""Build the native components: ``python -m katib_tpu.native.build``."""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

from . import NATIVE_DIR, OBSLOG_SO


def build(force: bool = False) -> bool:
    src = os.path.join(NATIVE_DIR, "obslog.cc")
    if os.path.exists(OBSLOG_SO) and not force:
        if os.path.getmtime(OBSLOG_SO) >= os.path.getmtime(src):
            return True
    gxx = shutil.which("g++") or shutil.which("c++")
    if gxx is None:
        print("no C++ compiler found; native obslog store unavailable", file=sys.stderr)
        return False
    cmd = [gxx, "-O2", "-fPIC", "-shared", "-std=c++17", "-o", OBSLOG_SO, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        print(f"native build failed:\n{e.stderr}", file=sys.stderr)
        return False
    return True


if __name__ == "__main__":
    ok = build(force="--force" in sys.argv)
    print("built" if ok else "build failed:", OBSLOG_SO)
    sys.exit(0 if ok else 1)
