"""Native (C++) components, consumed via ctypes.

Build on demand with ``python -m katib_tpu.native.build`` (g++ -O2 -fPIC
-shared); every consumer falls back to the pure-Python implementation when
the shared object is missing, so the framework has no hard toolchain
dependency.
"""

from __future__ import annotations

import os

NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
OBSLOG_SO = os.path.join(NATIVE_DIR, "libobslog.so")
METRICS_TAILER_SO = os.path.join(NATIVE_DIR, "libmetricstailer.so")


def obslog_available() -> bool:
    return os.path.exists(OBSLOG_SO)


def tailer_available() -> bool:
    return os.path.exists(METRICS_TAILER_SO)
