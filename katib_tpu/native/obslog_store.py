"""ctypes binding for the native observation-log store (obslog.cc).

Drop-in ObservationStore implementation; ``open_native_store`` returns None
when the shared object is absent so callers fall back to SQLite
(katib_tpu.db.store.open_store semantics preserved).
"""

from __future__ import annotations

import ctypes
import math
import struct
import threading
from typing import List, Optional, Sequence

from ..db.store import MetricLog, ObservationStore
from . import OBSLOG_SO, obslog_available

_NAN = float("nan")


def _load_lib():
    lib = ctypes.CDLL(OBSLOG_SO)
    lib.obslog_open.restype = ctypes.c_void_p
    lib.obslog_open.argtypes = [ctypes.c_char_p]
    lib.obslog_report.restype = ctypes.c_int
    lib.obslog_report.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
    ]
    lib.obslog_get.restype = ctypes.POINTER(ctypes.c_char)
    lib.obslog_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_double,
        ctypes.c_double,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.obslog_delete.restype = ctypes.c_int
    lib.obslog_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.obslog_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
    lib.obslog_close.argtypes = [ctypes.c_void_p]
    return lib


class NativeObservationStore(ObservationStore):
    def __init__(self, path: str):
        self._lib = _load_lib()
        self._lock = threading.Lock()
        self._handle = self._lib.obslog_open(path.encode())
        if not self._handle:
            raise OSError(f"cannot open native observation log at {path}")

    def report_observation_log(self, trial_name: str, logs: Sequence[MetricLog]) -> None:
        n = len(logs)
        if n == 0:
            return
        times = (ctypes.c_double * n)(*[l.timestamp for l in logs])
        metrics = (ctypes.c_char_p * n)(*[l.metric_name.encode() for l in logs])
        values = (ctypes.c_char_p * n)(*[str(l.value).encode() for l in logs])
        with self._lock:
            rc = self._lib.obslog_report(
                self._handle, trial_name.encode(), times, metrics, values, n
            )
        if rc != 0:
            raise OSError("native observation log write failed")

    def get_observation_log(
        self,
        trial_name: str,
        metric_name: Optional[str] = None,
        start_time: Optional[float] = None,
        end_time: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> List[MetricLog]:
        size = ctypes.c_int64(0)
        with self._lock:
            buf = self._lib.obslog_get(
                self._handle,
                trial_name.encode(),
                metric_name.encode() if metric_name else None,
                _NAN if start_time is None else start_time,
                _NAN if end_time is None else end_time,
                ctypes.byref(size),
            )
        if not buf:
            return []
        try:
            raw = ctypes.string_at(buf, size.value)
        finally:
            self._lib.obslog_free(buf)
        (n,) = struct.unpack_from("<i", raw, 0)
        pos = 4
        out: List[MetricLog] = []
        for _ in range(n):
            t, mlen, vlen = struct.unpack_from("<dHH", raw, pos)
            pos += 12
            metric = raw[pos : pos + mlen].decode()
            pos += mlen
            value = raw[pos : pos + vlen].decode()
            pos += vlen
            out.append(MetricLog(timestamp=t, metric_name=metric, value=value))
            if limit is not None and len(out) >= limit:
                break  # C ABI takes no limit; rows arrive time-ordered
        return out

    def delete_observation_log(self, trial_name: str) -> None:
        with self._lock:
            self._lib.obslog_delete(self._handle, trial_name.encode())

    def close(self) -> None:
        with self._lock:
            if self._handle:
                self._lib.obslog_close(self._handle)
                self._handle = None


def open_native_store(path: str, auto_build: bool = True) -> Optional[NativeObservationStore]:
    if not obslog_available() and auto_build:
        from .build import build

        build()
    if not obslog_available():
        return None
    return NativeObservationStore(path)
